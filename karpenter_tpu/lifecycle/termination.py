"""Node termination: taint -> drain -> volumes -> instance delete.

Counterpart of pkg/controllers/node/termination (controller.go:91-190,
terminator/terminator.go, terminator/eviction.go): when a node carries
a deletion timestamp, taint it `disrupted:NoSchedule`, evict pods in
priority waves (non-critical non-daemon first, critical daemon last),
respect PDBs and the do-not-disrupt annotation (unless past the
nodeclaim's termination grace period), await volume detachment, then
remove the finalizer so the object — and through the nodeclaim
finalizer, the instance — goes away.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from karpenter_tpu.apis.v1.labels import (
    DISRUPTED_NO_SCHEDULE_TAINT,
    DO_NOT_DISRUPT_ANNOTATION,
    NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION,
    TERMINATION_FINALIZER,
)
from karpenter_tpu.apis.v1.nodeclaim import COND_DRAINED, COND_VOLUMES_DETACHED
from karpenter_tpu.kube.client import EvictionBlockedError, KubeClient
from karpenter_tpu.kube.objects import Node, ObjectMeta, Pod


log = logging.getLogger("karpenter.termination")

CRITICAL_PRIORITY = 2_000_000_000  # system-cluster-critical threshold


# eviction retry limiter constants (terminator/eviction.go: the queue
# uses an item-exponential rate limiter, 100ms base / 10s cap, so a
# PDB-blocked pod is retried with backoff instead of hammered).
# Retries are jittered (full jitter above the base floor): a drain
# evicting dozens of pods behind one PDB blocks them all at the same
# instant, and un-jittered exponential backoff would re-hammer the
# eviction subresource with the whole cohort in lockstep forever.
EVICT_BACKOFF_BASE_SECONDS = 0.1
EVICT_BACKOFF_MAX_SECONDS = 10.0


def _jittered_backoff(attempts: int, rng=None) -> float:
    """Delay for the n-th consecutive 429 (1-based): the base floor
    plus full jitter up to the capped exponential. Attempt 1 is the
    deterministic base (an isolated 429 retries promptly); later
    attempts spread the cohort."""
    import random as _random

    from karpenter_tpu.utils.backoff import capped_exponential

    cap = capped_exponential(
        attempts, EVICT_BACKOFF_BASE_SECONDS, EVICT_BACKOFF_MAX_SECONDS
    )
    r = (rng or _random).random()
    return EVICT_BACKOFF_BASE_SECONDS + r * (
        cap - EVICT_BACKOFF_BASE_SECONDS
    )


class EvictionQueue:
    """Per-pod eviction with PDB 429 backoff (terminator/eviction.go).

    Drain goes through the substrate's eviction subresource, so PDBs
    are enforced server-side; a 429 (EvictionBlockedError) records the
    pod with an exponential next-retry time and skips it until that
    elapses, mirroring the reference's rate-limited eviction workqueue.

    On the SIMULATION substrate only (no ReplicaSet controller or
    kube-scheduler behind the store), the queue additionally plays
    workload-owner: controller-owned non-daemon pods are resurrected
    as fresh pending pods, which the provisioner reschedules
    (typically onto replacement capacity the orchestration queue
    already launched). See _maybe_rebirth for the gating.
    """

    def __init__(self, kube: KubeClient, recorder=None, rng=None):
        self.kube = kube
        self.recorder = recorder
        self._rng = rng  # injectable for deterministic backoff tests
        self.blocked: dict[str, str] = {}  # pod key -> blocking pdb
        self._attempts: dict[str, int] = {}  # pod key -> 429 count
        self._retry_at: dict[str, float] = {}  # pod key -> next attempt
        # successors owed to finalizer-wedged pods: created the moment
        # the old pod finally leaves the store (prune), so a wedge
        # delays — never loses — the workload replica
        self._pending_rebirth: dict[str, Pod] = {}

    def evict(self, pod: Pod, now: Optional[float] = None, force: bool = False) -> bool:
        now = time.time() if now is None else now
        if not force:
            if now < self._retry_at.get(pod.key, 0.0):
                return False  # still backing off from the last 429
            try:
                # the eviction subresource: PDBs are enforced by the
                # API substrate, never re-checked client-side
                # (eviction.go:170-185)
                self.kube.evict(pod, now=now)
            except EvictionBlockedError as err:
                self.blocked[pod.key] = err.pdb
                n = self._attempts.get(pod.key, 0)
                self._attempts[pod.key] = n + 1
                self._retry_at[pod.key] = now + _jittered_backoff(
                    n + 1, rng=self._rng
                )
                return False
        else:
            # terminal bypass (stuck pods / past the grace deadline):
            # a direct delete, exactly the reference's forced path
            self.kube.delete(pod, now=now)
        self._forget(pod.key)
        self._record_evicted(pod, now)
        self._maybe_rebirth(pod)
        return True

    def _record_evicted(self, pod: Pod, now: float) -> None:
        if self.recorder is None:
            return
        from karpenter_tpu.events.recorder import Event

        self.recorder.publish(Event(
            kind="Pod", name=pod.metadata.name,
            namespace=pod.metadata.namespace, type="Normal",
            reason="Evicted", message="Evicted pod from terminating node",
        ), now=now)  # terminator/events/events.go:37

    def _maybe_rebirth(self, pod: Pod) -> None:
        """Successor fabrication, STRICTLY gated to the simulation
        substrate: the in-memory store has no ReplicaSet controller or
        kube-scheduler behind it, so the queue plays workload-owner
        for controller-owned pods. On a real cluster
        (simulates_workload_controllers=False) the actual workload
        controller recreates replicas — creating pods there would
        duplicate them. Bare (ownerless) pods are never recreated:
        evicting one is terminal in a real cluster too.

        Rebirth waits until the old pod actually left the store: a pod
        wedged terminating (finalizers) still owns its name, and a
        real ReplicaSet would not have its successor admitted under a
        colliding identity either — the successor is OWED and created
        by prune() when the wedge finally clears. The debt is durable:
        the wedged pod is annotated so a restarted operator rebuilds
        the pending set from the store (restore())."""
        if not getattr(self.kube, "simulates_workload_controllers", False):
            return
        if pod.owner_kind() in ("", "DaemonSet", "Node"):
            return
        if self.kube.get_pod(
            pod.metadata.namespace, pod.metadata.name
        ) is None:
            self.kube.create(rebirth_pod(pod))
        else:
            if pod.metadata.annotations.get(REBIRTH_OWED_ANNOTATION) != "true":
                pod.metadata.annotations[REBIRTH_OWED_ANNOTATION] = "true"
                self.kube.touch(pod)
            self._pending_rebirth[pod.key] = rebirth_pod(pod)

    def _forget(self, pod_key: str) -> None:
        self.blocked.pop(pod_key, None)
        self._attempts.pop(pod_key, None)
        self._retry_at.pop(pod_key, None)

    def prune(self) -> None:
        """Drop bookkeeping for pods that no longer exist (the
        reference's queue removes items on pod deletion events), and
        deliver successors owed to since-cleared wedged pods.

        O(tracked), never O(fleet): liveness is answered per tracked
        key through the mirror's O(1) get_pod — the queue only ever
        holds pods of actively-draining nodes, so drain bookkeeping
        must not cost a 100k-pod set build per reconcile."""
        def gone(key: str) -> bool:
            ns, _, name = key.partition("/")
            return self.kube.get_pod(ns, name) is None

        for key in list(self.blocked.keys() | self._retry_at.keys()):
            if gone(key):
                self._forget(key)
        for key, successor in list(self._pending_rebirth.items()):
            if gone(key):
                del self._pending_rebirth[key]
                self.kube.create(successor)
        self._report_pending()

    def _report_pending(self) -> None:
        """Per-shard backlog gauge: a wedged drain shows up as ITS
        shard's backlog, not an anonymous total."""
        from karpenter_tpu.metrics.store import STATE_SHARD_QUEUE_PENDING
        from karpenter_tpu.state.shards import shard_count, shard_of

        shards = shard_count()
        counts = [0] * shards
        for key in self.blocked.keys() | self._retry_at.keys():
            counts[shard_of(key, shards)] += 1
        for s, n in enumerate(counts):
            STATE_SHARD_QUEUE_PENDING.set(
                float(n), {"queue": "evict", "shard": str(s)}
            )

    def restore(self) -> int:
        """Rebuild the owed-successor set from the store after a
        restart: any pod still wedged terminating with the rebirth-owed
        annotation re-enters _pending_rebirth (checkpoint/resume — the
        store is the durable record). Returns how many were owed."""
        n = 0
        if not getattr(self.kube, "simulates_workload_controllers", False):
            return 0  # real cluster: never fabricate pods (see above)
        for pod in self.kube.pods():
            if (
                pod.is_terminating()
                and pod.metadata.annotations.get(REBIRTH_OWED_ANNOTATION)
                == "true"
            ):
                self._pending_rebirth[pod.key] = rebirth_pod(pod)
                n += 1
        return n


def rebirth_pod(pod: Pod) -> Pod:
    """A controller-owned pod's successor: same spec, unbound, new uid."""
    import copy

    spec = copy.deepcopy(pod.spec)
    spec.node_name = ""
    annotations = dict(pod.metadata.annotations)
    annotations.pop(REBIRTH_OWED_ANNOTATION, None)
    return Pod(
        metadata=ObjectMeta(
            name=pod.metadata.name,
            namespace=pod.metadata.namespace,
            labels=dict(pod.metadata.labels),
            annotations=annotations,
            owner_references=list(pod.metadata.owner_references),
        ),
        spec=spec,
    )


def _critical(pod: Pod) -> bool:
    return (
        pod.spec.priority >= CRITICAL_PRIORITY
        or pod.spec.priority_class_name in ("system-cluster-critical", "system-node-critical")
    )


def _drain_waves(pods: list[Pod]) -> list[list[Pod]]:
    """Eviction order (terminator.go groupPodsByPriority, mirroring
    graceful node shutdown): non-critical non-daemon, non-critical
    daemon, critical non-daemon, critical daemon."""
    waves: list[list[Pod]] = [[], [], [], []]
    for pod in pods:
        daemon = pod.owner_kind() == "DaemonSet"
        crit = _critical(pod)
        idx = (2 if crit else 0) + (1 if daemon else 0)
        waves[idx].append(pod)
    return [w for w in waves if w]


REBIRTH_OWED_ANNOTATION = "karpenter.sh/rebirth-owed"


def _stuck_past_grace(pod: Pod, now: float) -> bool:
    """Terminating pod wedged past its grace period (nil grace = the
    k8s default 30s): bypassed by drain AND exempt from volume waits —
    it will die with the node, so neither it nor its volumes may hold
    the finalizer."""
    if not pod.is_terminating():
        return False
    grace = pod.spec.termination_grace_period_seconds
    grace = 30.0 if grace is None else grace
    return now >= (pod.metadata.deletion_timestamp or now) + grace


def _tolerates_disrupted(pod: Pod) -> bool:
    """Pods tolerating the karpenter.sh/disrupted:NoSchedule taint are
    NOT drained (IsDrainable, utils/pod): they opted to ride the node
    down, so they neither get evicted nor block drain completion."""
    from karpenter_tpu.scheduling.taints import tolerates_pod

    return tolerates_pod([DISRUPTED_NO_SCHEDULE_TAINT], pod) is None


class TerminationController:
    def __init__(self, kube: KubeClient, cluster=None, recorder=None):
        from karpenter_tpu.kube.dirty import DirtyTracker

        self.kube = kube
        self.cluster = cluster
        self.recorder = recorder
        self.queue = EvictionQueue(kube, recorder=recorder)
        self.queue.restore()  # owed rebirths survive operator restarts
        self.dirty = DirtyTracker(kube).watch("Node")
        # nodes mid-termination: drain retries and volume waits emit no
        # further node events, so they stay on the every-tick path
        # until their finalizer drops — empty in steady state
        self._terminating: set[str] = set()
        self._last_deleting_sweep = 0.0

    def reconcile(self, node: Node, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        if node.metadata.deletion_timestamp is None:
            return
        if TERMINATION_FINALIZER not in node.metadata.finalizers:
            return

        # 1. taint so nothing new schedules (controller.go:91; terminator.go:55)
        if not any(t.key == DISRUPTED_NO_SCHEDULE_TAINT.key for t in node.spec.taints):
            node.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
            self.kube.update(node)

        claim = self._claim_for(node)
        deadline = self._termination_deadline(claim)

        # 2. drain (terminator.go:96-180)
        remaining = self._drain(node, deadline, now)
        if remaining:
            # only THIS node's PDB-blocked pods justify the warning —
            # the queue is shared across every terminating node, and
            # pods merely riding out their grace period are fine
            this_blocked = [p for p in remaining
                            if p.key in self.queue.blocked]
            if self.recorder is not None and this_blocked:
                from karpenter_tpu.events.recorder import Event

                self.recorder.publish(Event(
                    kind="Node", name=node.metadata.name, type="Warning",
                    reason="FailedDraining",
                    message=f"Failed to drain node, {len(remaining)} pods "
                            "are waiting to be evicted",
                ), now=now)  # terminator/events/events.go:57
            return  # wait for evictions / PDBs; retried next reconcile
        if claim is not None:
            claim.status_conditions.set_true(COND_DRAINED, now=now)

        # 3. volume detachment (controller.go:223-268)
        if not self._volumes_detached(node, now):
            if deadline is None or now < deadline:
                return
        if claim is not None:
            claim.status_conditions.set_true(COND_VOLUMES_DETACHED, now=now)
            self.kube.update(claim)

        # 4. done: pods that rode the node down (disrupted-taint
        # tolerators, stragglers) die with it — the kubelet/pod-GC
        # role in a real cluster; controller-owned ones are reborn
        # pending so the workload replica is recreated
        for pod in list(self.kube.pods_on_node(node.metadata.name)):
            if not pod.is_terminal():
                self.queue.evict(pod, now=now, force=True)
        # drop the finalizer; the nodeclaim finalizer performs the
        # instance delete once the node object is gone
        self.kube.remove_finalizer(node, TERMINATION_FINALIZER)

    def reconcile_all(self, now: Optional[float] = None) -> None:
        for node in list(self.kube.nodes()):
            self.reconcile(node, now=now)
        self.queue.prune()

    def reconcile_dirty(self, now: Optional[float] = None) -> None:
        """O(terminating nodes): only nodes carrying a deletion
        timestamp ever need this controller, and they're tracked from
        node events; drain/volume retries keep them in the set until
        the finalizer drops."""
        now_mono = now if now is not None else time.time()
        for key in self.dirty.drain("Node"):
            node = self.kube.get_node(key)
            if node is not None and node.metadata.deletion_timestamp is not None:
                self._terminating.add(key)
        # periodic invariant sweep: every deleting node is tracked even
        # if its deletion event was consumed elsewhere (a full-resync
        # tick) — same wedge class as the lifecycle controller's
        # deleting-claim re-queue; periodic so steady state stays
        # O(terminating nodes)
        if now_mono - self._last_deleting_sweep >= 30.0:
            self._last_deleting_sweep = now_mono
            for node in self.kube.nodes():
                if node.metadata.deletion_timestamp is not None:
                    self._terminating.add(node.metadata.name)
        if not self._terminating:
            if self.queue._pending_rebirth:
                self.queue.prune()
            return
        for key in list(self._terminating):
            node = self.kube.get_node(key)
            if node is None or node.metadata.deletion_timestamp is None:
                self._terminating.discard(key)
                continue
            self.reconcile(node, now=now)
            if self.kube.get_node(key) is None:
                self._terminating.discard(key)
        # eviction bookkeeping only exists while something drains;
        # owed successors must be delivered the moment the wedge clears
        if self.queue.blocked or self.queue._retry_at or self.queue._pending_rebirth:
            self.queue.prune()

    # -- helpers ---------------------------------------------------------------

    def _claim_for(self, node: Node):
        # O(1) through the cluster's name/provider-id index; re-read
        # through the mirror so the caller mutates (and updates) the
        # live object, not the cluster's view of it
        if self.cluster is not None:
            sn = self.cluster.node_for_name(node.metadata.name)
            if sn is not None and sn.node_claim is not None:
                claim = self.kube.get_node_claim(
                    sn.node_claim.metadata.name
                )
                if claim is not None and (
                    claim.status.provider_id == node.spec.provider_id
                ):
                    return claim
        # cluster-less fallback (bare-constructed controllers in
        # tests), or the index hasn't absorbed the claim yet
        for claim in self.kube.node_claims():
            if claim.status.provider_id == node.spec.provider_id:
                return claim
        return None

    def _termination_deadline(self, claim) -> Optional[float]:
        if claim is None:
            return None
        raw = claim.metadata.annotations.get(
            NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION
        )
        return float(raw) if raw else None

    def _blocking_pods(self, node: Node, now: Optional[float] = None) -> list[Pod]:
        """Pods whose presence blocks drain completion: live, not
        riding the node down via a disrupted-taint toleration, and not
        STUCK terminating past their own grace period (terminator.go
        'bypass pods which are stuck terminating past their grace
        period' — a wedged finalizer must not hold the node hostage)."""
        now = time.time() if now is None else now
        out = []
        for p in self.kube.pods_on_node(node.metadata.name):
            if p.is_terminal() or _tolerates_disrupted(p):
                continue
            if _stuck_past_grace(p, now):
                continue  # wedged past grace: bypassed
            out.append(p)
        return out

    def _drain(self, node: Node, deadline: Optional[float], now: float) -> list[Pod]:
        """Evict one wave at a time; returns pods still on the node
        that block completion. Like the reference (terminator.go
        Drain), the first non-empty wave gates the rest — a
        do-not-disrupt pod in it stalls drain until the TGP deadline."""
        pods = self._blocking_pods(node, now)
        if deadline is not None:
            # ahead-of-deadline deletion (terminator.go:140-180): a pod
            # whose terminationGracePeriodSeconds would run PAST the
            # node's TGP deadline is deleted NOW — proactively, PDBs and
            # waves notwithstanding — so it gets as much of its grace as
            # the node has left (the remaining time is the clamped grace
            # the reference passes in DeleteOptions)
            expired = False
            for pod in pods:
                grace = pod.spec.termination_grace_period_seconds
                if grace is None or pod.is_terminating():
                    continue
                if now >= deadline - grace:
                    log.info(
                        "deleting pod %s ahead of node TGP deadline "
                        "(grace %ss clamped to %.0fs)",
                        pod.key, grace, max(0.0, deadline - now),
                    )
                    self.queue.evict(pod, now=now, force=True)
                    expired = True
            if expired:
                pods = self._blocking_pods(node, now)
        waves = _drain_waves([p for p in pods if not p.is_terminating()])
        if waves:
            force = deadline is not None and now >= deadline
            for pod in waves[0]:
                if (
                    pod.metadata.annotations.get(DO_NOT_DISRUPT_ANNOTATION) == "true"
                    and not force
                ):
                    continue
                # TGP enforcement bypasses PDBs (terminator.go:140)
                self.queue.evict(pod, now=now, force=force)
        return self._blocking_pods(node, now)

    def _volumes_detached(self, node: Node, now: float) -> bool:
        """Only volumes of DRAINABLE pods gate termination
        (controller.go 'should only wait for volume attachments
        associated with drainable pods'): a volume still claimed by a
        pod that will die WITH the node — a disrupted-taint rider or a
        wedged pod the drain bypassed past its grace — can never detach
        first and must not wedge the finalizer."""
        attached = [
            pv for pv in self.kube.list("PersistentVolume")
            if pv.attached_node == node.metadata.name
        ]
        if not attached:
            return True
        riders = [
            p for p in self.kube.pods_on_node(node.metadata.name)
            if not p.is_terminal()
            and (_tolerates_disrupted(p) or _stuck_past_grace(p, now))
        ]
        from karpenter_tpu.provisioning.volume_topology import _pvc_name_for

        rider_pv_names = set()
        for pod in riders:
            for volume in pod.spec.volumes:
                pvc_name = _pvc_name_for(pod, volume)
                if not pvc_name:
                    continue
                pvc = self.kube.get_pvc(pod.metadata.namespace, pvc_name)
                if pvc is not None and pvc.spec.volume_name:
                    rider_pv_names.add(pvc.spec.volume_name)
        return all(pv.metadata.name in rider_pv_names for pv in attached)
