"""Hygiene controllers: consistency, hydration, nodepool status.

- ConsistencyController (consistency/controller.go:79-150 +
  nodeshape.go:35): verifies a registered node's real capacity is
  within 10% of what the claim requested; emits an event and sets
  ConsistentStateFound.
- HydrationController (nodeclaim/hydration, node/hydration): back-fills
  nodepool-hash annotations on objects created before the annotation
  existed (upgrade path).
- NodePoolStatusController folds the reference's nodepool/{counter,
  readiness, registrationhealth, validation, hash} controllers: tallies
  owned resources into status, mirrors NodeClassReady, sets
  NodeRegistrationHealthy from the health tracker, validates the spec,
  and propagates template-hash changes to claims.
"""

from __future__ import annotations

import time
from typing import Optional

from karpenter_tpu.apis.v1.labels import (
    NODEPOOL_HASH_ANNOTATION,
    NODEPOOL_HASH_VERSION,
    NODEPOOL_HASH_VERSION_ANNOTATION,
    NODEPOOL_LABEL,
    is_restricted_label,
)
from karpenter_tpu.apis.v1.nodeclaim import (
    COND_CONSISTENT_STATE_FOUND,
    COND_REGISTERED,
)
from karpenter_tpu.apis.v1.nodepool import (
    COND_NODE_CLASS_READY,
    COND_NODE_REGISTRATION_HEALTHY,
    COND_VALIDATION_SUCCEEDED,
)
from karpenter_tpu.events.recorder import Event, EventRecorder
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.nodepoolhealth import HealthTracker
from karpenter_tpu.utils import resources as resutil
from karpenter_tpu.utils.duration import CronSchedule, parse_duration

SHAPE_TOLERANCE = 0.10  # nodeshape.go:35


class ConsistencyController:
    def __init__(self, kube: KubeClient, recorder: Optional[EventRecorder] = None):
        self.kube = kube
        self.recorder = recorder or EventRecorder()

    def reconcile_all(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        nodes_by_pid = {n.spec.provider_id: n for n in self.kube.nodes()}
        for claim in self.kube.node_claims():
            if not claim.status_conditions.is_true(COND_REGISTERED):
                continue
            node = nodes_by_pid.get(claim.status.provider_id)
            if node is None:
                continue
            consistent = True
            for key, expected in claim.status.capacity.items():
                actual = node.status.capacity.get(key, 0.0)
                if expected > 0 and actual < expected * (1 - SHAPE_TOLERANCE):
                    consistent = False
                    self.recorder.publish(
                        Event(
                            kind="NodeClaim", name=claim.metadata.name,
                            type="Warning", reason="FailedConsistencyCheck",
                            message=f"node {node.metadata.name} {key} "
                                    f"{actual} < expected {expected}",
                        ),
                        now=now,
                    )
            if consistent:
                claim.status_conditions.set_true(COND_CONSISTENT_STATE_FOUND, now=now)
            else:
                claim.status_conditions.set_false(
                    COND_CONSISTENT_STATE_FOUND, "ConsistencyCheckFailed", now=now
                )


class HydrationController:
    def __init__(self, kube: KubeClient):
        self.kube = kube

    def reconcile_all(self) -> int:
        hydrated = 0
        pools = {p.metadata.name: p for p in self.kube.node_pools()}
        for obj in list(self.kube.node_claims()) + list(self.kube.nodes()):
            pool = pools.get(obj.metadata.labels.get(NODEPOOL_LABEL, ""))
            if pool is None:
                continue
            if NODEPOOL_HASH_VERSION_ANNOTATION not in obj.metadata.annotations:
                obj.metadata.annotations[NODEPOOL_HASH_VERSION_ANNOTATION] = (
                    NODEPOOL_HASH_VERSION
                )
                obj.metadata.annotations[NODEPOOL_HASH_ANNOTATION] = pool.hash()
                hydrated += 1
        return hydrated


class NodePoolStatusController:
    def __init__(self, kube: KubeClient, cluster: Cluster,
                 health: Optional[HealthTracker] = None,
                 nodeclass_ready: bool = True):
        self.kube = kube
        self.cluster = cluster
        self.health = health or HealthTracker()
        self.nodeclass_ready = nodeclass_ready

    def reconcile_all(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for pool in self.kube.node_pools():
            self._counter(pool)
            self._readiness(pool, now)
            self._registration_health(pool, now)
            self._validate(pool, now)
            self._hash_propagation(pool)

    def _counter(self, pool) -> None:
        """nodepool/counter: aggregate owned capacity into status."""
        total: dict[str, float] = {}
        count = 0
        for node in self.cluster.nodes():
            if node.nodepool_name() != pool.metadata.name or node.deleting():
                continue
            total = resutil.merge(total, node.capacity())
            count += 1
        pool.status.resources = total
        pool.status.nodes = count

    def _readiness(self, pool, now: float) -> None:
        if self.nodeclass_ready:
            pool.status_conditions.set_true(COND_NODE_CLASS_READY, now=now)
        else:
            pool.status_conditions.set_false(
                COND_NODE_CLASS_READY, "NodeClassNotReady", now=now
            )

    def _registration_health(self, pool, now: float) -> None:
        if self.health.healthy(pool.metadata.name):
            pool.status_conditions.set_true(COND_NODE_REGISTRATION_HEALTHY, now=now)
        else:
            pool.status_conditions.set_false(
                COND_NODE_REGISTRATION_HEALTHY, "RegistrationFailuresExceeded", now=now
            )

    def _validate(self, pool, now: float) -> None:
        """Runtime validation (nodepool/validation + CEL-rule analog)."""
        errors = []
        for key in pool.spec.template.labels:
            err = is_restricted_label(key)
            if err:
                errors.append(err)
        for budget in pool.spec.disruption.budgets:
            if budget.schedule is not None:
                try:
                    CronSchedule.parse(budget.schedule)
                except ValueError as err:
                    errors.append(str(err))
            if not budget.nodes.endswith("%"):
                try:
                    int(budget.nodes)
                except ValueError:
                    errors.append(f"invalid budget nodes {budget.nodes!r}")
        try:
            parse_duration(pool.spec.disruption.consolidate_after)
        except ValueError as err:
            errors.append(str(err))
        if errors:
            pool.status_conditions.set_false(
                COND_VALIDATION_SUCCEEDED, "ValidationFailed", "; ".join(errors), now=now
            )
        else:
            pool.status_conditions.set_true(COND_VALIDATION_SUCCEEDED, now=now)

    def _hash_propagation(self, pool) -> None:
        """nodepool/hash: stamp current template hash onto owned claims
        at matching hash version (drift detection input)."""
        current = pool.hash()
        for claim in self.kube.node_claims():
            if claim.metadata.labels.get(NODEPOOL_LABEL) != pool.metadata.name:
                continue
            version = claim.metadata.annotations.get(NODEPOOL_HASH_VERSION_ANNOTATION)
            if version != NODEPOOL_HASH_VERSION:
                claim.metadata.annotations[NODEPOOL_HASH_VERSION_ANNOTATION] = (
                    NODEPOOL_HASH_VERSION
                )
                claim.metadata.annotations[NODEPOOL_HASH_ANNOTATION] = current
