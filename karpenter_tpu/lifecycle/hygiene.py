"""Hygiene controllers: consistency, hydration, nodepool status.

- ConsistencyController (consistency/controller.go:79-150 +
  nodeshape.go:35): verifies a registered node's real capacity is
  within 10% of what the claim requested; emits an event and sets
  ConsistentStateFound.
- HydrationController (nodeclaim/hydration, node/hydration): back-fills
  nodepool-hash annotations on objects created before the annotation
  existed (upgrade path).
- NodePoolStatusController folds the reference's nodepool/{counter,
  readiness, registrationhealth, validation, hash} controllers: tallies
  owned resources into status, mirrors NodeClassReady, sets
  NodeRegistrationHealthy from the health tracker, validates the spec,
  and propagates template-hash changes to claims.
"""

from __future__ import annotations

import time
from typing import Optional

from karpenter_tpu.apis.v1.labels import (
    NODEPOOL_HASH_ANNOTATION,
    NODEPOOL_HASH_VERSION,
    NODEPOOL_HASH_VERSION_ANNOTATION,
    NODEPOOL_LABEL,
    is_restricted_label,
)
from karpenter_tpu.apis.v1.nodeclaim import (
    COND_CONSISTENT_STATE_FOUND,
    COND_REGISTERED,
)
from karpenter_tpu.apis.v1.nodepool import (
    COND_NODE_CLASS_READY,
    COND_NODE_REGISTRATION_HEALTHY,
    COND_VALIDATION_SUCCEEDED,
)
from karpenter_tpu.events.recorder import Event, EventRecorder
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.nodepoolhealth import HealthTracker
from karpenter_tpu.utils import resources as resutil
from karpenter_tpu.utils.duration import CronSchedule, parse_duration

SHAPE_TOLERANCE = 0.10  # nodeshape.go:35


class ConsistencyController:
    def __init__(self, kube: KubeClient, recorder: Optional[EventRecorder] = None):
        from karpenter_tpu.kube.dirty import DirtyTracker

        self.kube = kube
        self.recorder = recorder or EventRecorder()
        self.dirty = DirtyTracker(kube).watch("NodeClaim", "Node")

    def reconcile_dirty(self, now: Optional[float] = None) -> None:
        """O(changes): the shape invariant can only break when the
        claim or its node changed."""
        now = time.time() if now is None else now
        claim_keys = self.dirty.drain("NodeClaim")
        node_keys = self.dirty.drain("Node")
        if not claim_keys and not node_keys:
            return
        pids = set()
        for key in node_keys:
            node = self.kube.get_node(key)
            if node is not None:
                pids.add(node.spec.provider_id)
        claims = [
            c for c in self.kube.node_claims()
            if c.key in claim_keys or c.status.provider_id in pids
        ]
        self._check(claims, now)

    def reconcile_all(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        self._check(list(self.kube.node_claims()), now)

    def _check(self, claims, now: float) -> None:
        nodes_by_pid = {n.spec.provider_id: n for n in self.kube.nodes()}
        for claim in claims:
            if not claim.status_conditions.is_true(COND_REGISTERED):
                continue
            node = nodes_by_pid.get(claim.status.provider_id)
            if node is None:
                # a Registered claim with no live node is the crash-
                # recovery window (node deleted by another actor, or an
                # operator died between two registration writes):
                # surface it on the condition so readiness dashboards
                # see the inconsistency while GC converges it
                if claim.metadata.deletion_timestamp is None:
                    claim.status_conditions.set_false(
                        COND_CONSISTENT_STATE_FOUND, "NodeMissing", now=now
                    )
                continue
            consistent = True
            for key, expected in claim.status.capacity.items():
                actual = node.status.capacity.get(key, 0.0)
                if expected > 0 and actual < expected * (1 - SHAPE_TOLERANCE):
                    consistent = False
                    self.recorder.publish(
                        Event(
                            kind="NodeClaim", name=claim.metadata.name,
                            type="Warning", reason="FailedConsistencyCheck",
                            message=f"node {node.metadata.name} {key} "
                                    f"{actual} < expected {expected}",
                        ),
                        now=now,
                    )
            if consistent:
                claim.status_conditions.set_true(COND_CONSISTENT_STATE_FOUND, now=now)
            else:
                claim.status_conditions.set_false(
                    COND_CONSISTENT_STATE_FOUND, "ConsistencyCheckFailed", now=now
                )


class HydrationController:
    def __init__(self, kube: KubeClient):
        from karpenter_tpu.kube.dirty import DirtyTracker

        self.kube = kube
        self.dirty = DirtyTracker(kube).watch("NodeClaim", "Node")

    def _hydrate(self, obj, pools) -> int:
        pool = pools.get(obj.metadata.labels.get(NODEPOOL_LABEL, ""))
        if pool is None:
            return 0
        if NODEPOOL_HASH_VERSION_ANNOTATION not in obj.metadata.annotations:
            obj.metadata.annotations[NODEPOOL_HASH_VERSION_ANNOTATION] = (
                NODEPOOL_HASH_VERSION
            )
            obj.metadata.annotations[NODEPOOL_HASH_ANNOTATION] = pool.hash()
            return 1
        return 0

    def reconcile_all(self) -> int:
        hydrated = 0
        pools = {p.metadata.name: p for p in self.kube.node_pools()}
        for obj in list(self.kube.node_claims()) + list(self.kube.nodes()):
            hydrated += self._hydrate(obj, pools)
        return hydrated

    def reconcile_dirty(self) -> int:
        """O(changes): hydration is a one-shot upgrade backfill — only
        objects that just appeared or changed can need it."""
        keys = self.dirty.drain("NodeClaim") | {
            ("Node", k) for k in self.dirty.drain("Node")
        }
        if not keys:
            return 0
        pools = {p.metadata.name: p for p in self.kube.node_pools()}
        hydrated = 0
        for key in keys:
            if isinstance(key, tuple):
                obj = self.kube.get_node(key[1])
            else:
                obj = self.kube.get_node_claim(key)
            if obj is not None:
                hydrated += self._hydrate(obj, pools)
        return hydrated


class NodePoolStatusController:
    def __init__(self, kube: KubeClient, cluster: Cluster,
                 health: Optional[HealthTracker] = None,
                 nodeclass_ready: bool = True):
        from karpenter_tpu.kube.dirty import DirtyTracker

        self.kube = kube
        self.cluster = cluster
        self.health = health or HealthTracker()
        self.nodeclass_ready = nodeclass_ready
        self.dirty = DirtyTracker(kube).watch("NodeClaim", "Node")
        self._pool_hashes: dict[str, str] = {}

    def reconcile_all(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for pool in self.kube.node_pools():
            self._counter(pool)
            self._readiness(pool, now)
            self._registration_health(pool, now)
            self._validate(pool, now)
            self._hash_propagation(pool)

    def reconcile_dirty(self, now: Optional[float] = None) -> None:
        """Per-pool condition upkeep stays (pools are few and the work
        is O(1) per pool); the O(cluster) parts — node-capacity
        aggregation and hash propagation over owned claims — run only
        when node/claim events or a pool-hash change demand it."""
        now = time.time() if now is None else now
        nodes_changed = bool(self.dirty.drain("Node"))
        claim_keys = self.dirty.drain("NodeClaim")
        for pool in self.kube.node_pools():
            if nodes_changed or claim_keys:
                self._counter(pool)
            self._readiness(pool, now)
            self._registration_health(pool, now)
            self._validate(pool, now)
            current = pool.hash()
            if self._pool_hashes.get(pool.metadata.name) != current:
                # template changed: every owned claim needs the stamp
                self._pool_hashes[pool.metadata.name] = current
                self._hash_propagation(pool)
            elif claim_keys:
                for key in claim_keys:
                    claim = self.kube.get_node_claim(key)
                    if (
                        claim is not None
                        and claim.metadata.labels.get(NODEPOOL_LABEL)
                        == pool.metadata.name
                    ):
                        self._stamp_claim(claim, current)

    def _counter(self, pool) -> None:
        """nodepool/counter: aggregate owned capacity into status."""
        total: dict[str, float] = {}
        count = 0
        for node in self.cluster.nodes():
            if node.nodepool_name() != pool.metadata.name or node.deleting():
                continue
            total = resutil.merge(total, node.capacity())
            count += 1
        pool.status.resources = total
        pool.status.nodes = count

    def _readiness(self, pool, now: float) -> None:
        if self.nodeclass_ready:
            pool.status_conditions.set_true(COND_NODE_CLASS_READY, now=now)
        else:
            pool.status_conditions.set_false(
                COND_NODE_CLASS_READY, "NodeClassNotReady", now=now
            )

    def _registration_health(self, pool, now: float) -> None:
        if self.health.healthy(pool.metadata.name):
            pool.status_conditions.set_true(COND_NODE_REGISTRATION_HEALTHY, now=now)
        else:
            pool.status_conditions.set_false(
                COND_NODE_REGISTRATION_HEALTHY, "RegistrationFailuresExceeded", now=now
            )

    def _validate(self, pool, now: float) -> None:
        """Runtime validation (nodepool/validation + CEL-rule analog)."""
        errors = []
        for key in pool.spec.template.labels:
            err = is_restricted_label(key)
            if err:
                errors.append(err)
        for budget in pool.spec.disruption.budgets:
            if budget.schedule is not None:
                try:
                    CronSchedule.parse(budget.schedule)
                except ValueError as err:
                    errors.append(str(err))
            if not budget.nodes.endswith("%"):
                try:
                    int(budget.nodes)
                except ValueError:
                    errors.append(f"invalid budget nodes {budget.nodes!r}")
        try:
            parse_duration(pool.spec.disruption.consolidate_after)
        except ValueError as err:
            errors.append(str(err))
        if errors:
            pool.status_conditions.set_false(
                COND_VALIDATION_SUCCEEDED, "ValidationFailed", "; ".join(errors), now=now
            )
        else:
            pool.status_conditions.set_true(COND_VALIDATION_SUCCEEDED, now=now)

    def _hash_propagation(self, pool) -> None:
        """nodepool/hash: stamp current template hash onto owned claims
        at matching hash version (drift detection input)."""
        current = pool.hash()
        for claim in self.kube.node_claims():
            if claim.metadata.labels.get(NODEPOOL_LABEL) != pool.metadata.name:
                continue
            self._stamp_claim(claim, current)

    def _stamp_claim(self, claim, current: str) -> None:
        version = claim.metadata.annotations.get(NODEPOOL_HASH_VERSION_ANNOTATION)
        if version != NODEPOOL_HASH_VERSION:
            claim.metadata.annotations[NODEPOOL_HASH_VERSION_ANNOTATION] = (
                NODEPOOL_HASH_VERSION
            )
            claim.metadata.annotations[NODEPOOL_HASH_ANNOTATION] = current
