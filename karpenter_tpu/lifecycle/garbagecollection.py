"""Garbage collection + node health (repair) controllers.

GC (nodeclaim/garbagecollection/controller.go:60-118): periodically
lists the cloud provider and deletes instances with no matching claim,
plus claims whose registered node vanished.

Health (node/health/controller.go:56-200): feature-gated auto-repair —
nodes matching a provider RepairPolicy condition beyond its toleration
are force-deleted, unless >20% of the cluster is unhealthy (circuit
breaker). Repair bypasses the termination grace period.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from karpenter_tpu.apis.v1.labels import NODEPOOL_LABEL
from karpenter_tpu.apis.v1.nodeclaim import COND_REGISTERED
from karpenter_tpu.cloudprovider.types import CloudProvider, NodeClaimNotFoundError
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.metrics.store import OPERATOR_RECOVERY
from karpenter_tpu.operator.options import Options

log = logging.getLogger("karpenter.gc")

GC_INTERVAL_SECONDS = 2 * 60
UNHEALTHY_CLUSTER_THRESHOLD = 0.2  # health circuit breaker


class GarbageCollectionController:
    def __init__(self, kube: KubeClient, cloud: CloudProvider):
        self.kube = kube
        self.cloud = cloud

    def reconcile(self, now: Optional[float] = None) -> dict[str, int]:
        now = time.time() if now is None else now
        stats = {"leaked_instances": 0, "orphaned_claims": 0,
                 "orphaned_nodes": 0}
        claims = {c.status.provider_id: c for c in self.kube.node_claims()
                  if c.status.provider_id}
        # leaked cloud instances with no claim — including the
        # double-launch window: a crash between the provider create and
        # the claim's status write leaves a running instance no claim
        # records; the restarted operator re-launches, and this pass
        # reaps the unrecorded twin
        for remote in self.cloud.list():
            pid = remote.status.provider_id
            if pid and pid not in claims:
                try:
                    self.cloud.delete(remote)
                    stats["leaked_instances"] += 1
                    OPERATOR_RECOVERY.inc({"action": "reaped_leak"})
                    log.info("gc: deleted leaked instance %s", pid)
                except NodeClaimNotFoundError:
                    pass
        # claims whose node disappeared after registration
        node_pids = {n.spec.provider_id for n in self.kube.nodes()}
        for claim in self.kube.node_claims():
            if claim.metadata.deletion_timestamp is not None:
                continue
            if not claim.status_conditions.is_true(COND_REGISTERED):
                continue
            if claim.status.provider_id not in node_pids:
                self.kube.delete(claim, now=now)
                stats["orphaned_claims"] += 1
                log.info("gc: deleted orphaned claim %s", claim.metadata.name)
        # karpenter-managed Node objects whose backing instance AND
        # claim are both gone (the node a reaped leaked instance had
        # already materialized): nothing else deletes these — the claim
        # cascade never knew them. Instance liveness is checked AFTER
        # the leak pass so a just-reaped twin's node goes too.
        live_pids = {
            i.status.provider_id for i in self.cloud.list()
            if i.status.provider_id
        }
        for node in self.kube.nodes():
            if NODEPOOL_LABEL not in node.metadata.labels:
                continue  # bring-your-own nodes are never GC'd
            pid = node.spec.provider_id
            if pid and pid not in live_pids and pid not in claims:
                self.kube.delete(node, now=now)
                stats["orphaned_nodes"] += 1
                log.info("gc: deleted orphaned node %s", node.metadata.name)
        return stats


class NodeHealthController:
    def __init__(self, kube: KubeClient, cloud: CloudProvider,
                 options: Optional[Options] = None):
        self.kube = kube
        self.cloud = cloud
        self.options = options or Options()

    def reconcile(self, now: Optional[float] = None) -> list[str]:
        """Returns names of nodes sent for repair."""
        if not self.options.feature_gates.node_repair:
            return []
        now = time.time() if now is None else now
        policies = self.cloud.repair_policies()
        if not policies:
            return []
        nodes = self.kube.nodes()
        if not nodes:
            return []
        unhealthy = []
        for node in nodes:
            for policy in policies:
                cond = node.condition(policy.condition_type)
                if cond is None or cond.status != policy.condition_status:
                    continue
                if now - cond.last_transition_time >= policy.toleration_duration:
                    unhealthy.append(node)
                    break
        # circuit breaker: abstain when >20% of the cluster is unhealthy
        if len(unhealthy) / len(nodes) > UNHEALTHY_CLUSTER_THRESHOLD and len(nodes) > 1:
            log.warning("node repair: %d/%d nodes unhealthy; circuit breaker open",
                        len(unhealthy), len(nodes))
            return []
        repaired = []
        for node in unhealthy:
            claim = next(
                (c for c in self.kube.node_claims()
                 if c.status.provider_id == node.spec.provider_id), None
            )
            if claim is not None and claim.metadata.deletion_timestamp is None:
                # repair bypasses TGP: drop the annotation path entirely
                self.kube.delete(claim, now=now)
                repaired.append(node.metadata.name)
                log.info("node repair: deleting unhealthy node %s", node.metadata.name)
        return repaired
