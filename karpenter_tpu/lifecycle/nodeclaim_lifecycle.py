"""NodeClaim lifecycle: launch -> registration -> initialization -> liveness.

Counterpart of pkg/controllers/nodeclaim/lifecycle (controller.go:119-183
and launch/registration/initialization/liveness sub-reconcilers), plus
the finalize path (controller.go:184-273) that tears the instance down
when a claim is deleted.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from karpenter_tpu.apis.v1.labels import (
    NODE_INITIALIZED_LABEL,
    NODE_REGISTERED_LABEL,
    NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION,
    NODEPOOL_LABEL,
    TERMINATION_FINALIZER,
    UNREGISTERED_TAINT_KEY,
)
from karpenter_tpu.apis.v1.nodeclaim import (
    COND_INITIALIZED,
    COND_INSTANCE_TERMINATING,
    COND_LAUNCHED,
    COND_REGISTERED,
    NodeClaim,
)
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    NodeClassNotReadyError,
)
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.metrics.store import NODECLAIMS_TERMINATED
from karpenter_tpu.kube.objects import Node, OwnerReference
from karpenter_tpu.scheduling.taints import is_ephemeral
from karpenter_tpu.state.nodepoolhealth import HealthTracker
from karpenter_tpu.utils.duration import parse_duration
from karpenter_tpu.utils.resources import fits

log = logging.getLogger("karpenter.lifecycle")

LAUNCH_TIMEOUT_SECONDS = 5 * 60       # liveness.go:51
REGISTRATION_TIMEOUT_SECONDS = 15 * 60  # liveness.go:56
# transient launch failures retry with capped, full-jittered
# exponential backoff: a provider outage fails every in-flight claim
# at once, and tick-paced un-jittered retries would re-hammer the
# provider API with the whole cohort in lockstep each reconcile
LAUNCH_BACKOFF_BASE_SECONDS = 1.0
LAUNCH_BACKOFF_MAX_SECONDS = 30.0
# how often reconcile_dirty re-queues every deleting claim (wedge
# recovery bound; event-tracked claims progress every pass regardless)
DELETING_SWEEP_SECONDS = 30.0


class NodeClaimLifecycle:
    def __init__(
        self,
        kube: KubeClient,
        cloud_provider: CloudProvider,
        health: Optional[HealthTracker] = None,
    ):
        from karpenter_tpu.kube.dirty import DirtyTracker

        self.kube = kube
        self.cloud_provider = cloud_provider
        self.health = health or HealthTracker()
        self.dirty = DirtyTracker(kube).watch("NodeClaim", "Node")
        # claims mid-flight (not yet Initialized, or deleting): these
        # progress on liveness clocks and cloud ticks that emit no
        # object event, so they stay on the every-tick path until they
        # settle — in steady state the set is empty
        self._active: set[str] = set()
        self._last_deleting_sweep = 0.0
        # claim key -> (consecutive launch failures, next attempt at)
        self._launch_retry: dict[str, tuple[int, float]] = {}

    # -- entry ----------------------------------------------------------------

    def reconcile(self, claim: NodeClaim, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        before = self._signature(claim)
        if claim.metadata.deletion_timestamp is not None:
            self._finalize(claim, now)
        else:
            self._launch(claim, now)
            if claim.status_conditions.is_true(COND_LAUNCHED):
                self._register(claim, now)
            if claim.status_conditions.is_true(COND_REGISTERED):
                self._initialize(claim, now)
            self._liveness(claim, now)
        if self._signature(claim) != before:
            # conditions were set in place; announce so watch-driven
            # consumers (conditions, hygiene, metrics) see the change
            self.kube.touch(claim)

    def reconcile_all(self, now: Optional[float] = None) -> None:
        for claim in list(self.kube.node_claims()):
            self.reconcile(claim, now)

    def reconcile_dirty(self, now: Optional[float] = None) -> None:
        """O(changes + in-flight): dirty claims (object events, incl.
        node events mapped back via nodeName) plus the active set of
        claims still progressing through launch/register/initialize or
        finalize."""
        now = time.time() if now is None else now
        keys = self.dirty.drain("NodeClaim")
        # Periodic deleting-claim sweep (controller-runtime requeues
        # deleting objects until their finalizer clears): the finalize
        # chain needs multiple passes, and an event race that drops a
        # claim from the active set mid-chain would otherwise wedge it
        # deleting forever with its instance still running (found by
        # the round-5 randomized soak). Periodic, not per-pass, so the
        # steady state stays O(changes + in-flight).
        if now - self._last_deleting_sweep >= DELETING_SWEEP_SECONDS:
            self._last_deleting_sweep = now
            keys |= {
                c.key for c in self.kube.node_claims()
                if c.metadata.deletion_timestamp is not None
            }
        node_keys = self.dirty.drain("Node")
        if node_keys:
            # one pid->claim index per pass, not a claim scan per node
            # (mass registration would otherwise cost
            # O(dirty_nodes x claims))
            by_pid = {
                c.status.provider_id: c.key
                for c in self.kube.node_claims()
                if c.status.provider_id
            }
            for node_key in node_keys:
                node = self.kube.get_node(node_key)
                if node is None:
                    continue
                hit = by_pid.get(node.spec.provider_id)
                if hit is not None:
                    keys.add(hit)
        keys |= self._active
        for key in keys:
            claim = self.kube.get_node_claim(key)
            if claim is None:
                self._active.discard(key)
                self._launch_retry.pop(key, None)
                continue
            self.reconcile(claim, now)
            settled = (
                claim.metadata.deletion_timestamp is None
                and claim.status_conditions.is_true(COND_INITIALIZED)
            )
            live = self.kube.get_node_claim(key) is not None
            if settled or not live:
                self._active.discard(key)
            else:
                self._active.add(key)

    def adopt_in_flight(self) -> int:
        """Crash recovery (Operator._recover): re-enter every claim
        still progressing — not yet Initialized, or mid-deletion —
        into the active set, so a restarted operator advances them on
        its own clocks instead of waiting for watch traffic the old
        process already consumed. Idempotent; returns how many claims
        are in flight."""
        adopted = 0
        for claim in self.kube.node_claims():
            settled = (
                claim.metadata.deletion_timestamp is None
                and claim.status_conditions.is_true(COND_INITIALIZED)
            )
            if not settled:
                self._active.add(claim.key)
                adopted += 1
        return adopted

    def _signature(self, claim: NodeClaim) -> tuple:
        return (
            claim.status.provider_id,
            claim.status.node_name,
            tuple(
                (c.type, c.status)
                for c in claim.status_conditions.conditions
            ),
            len(claim.metadata.finalizers),
        )

    # -- launch (launch.go:45-125) --------------------------------------------

    def _launch(self, claim: NodeClaim, now: float) -> None:
        if claim.status.provider_id:
            claim.status_conditions.set_true(COND_LAUNCHED, now=now)
            self._launch_retry.pop(claim.key, None)
            return
        retry = self._launch_retry.get(claim.key)
        if retry is not None and now < retry[1]:
            return  # still backing off from the last transient failure
        try:
            launched = self.cloud_provider.create(claim)
        except (InsufficientCapacityError, NodeClassNotReadyError) as err:
            # ICE: delete the claim so pods reschedule elsewhere
            log.info("launch failed for %s: %s; deleting claim", claim.metadata.name, err)
            self.health.record(claim.metadata.labels.get(NODEPOOL_LABEL, ""), False)
            self._launch_retry.pop(claim.key, None)
            self._delete_claim(claim, now)
            return
        except Exception as err:
            from karpenter_tpu.utils.backoff import (
                capped_exponential,
                jitter,
            )

            n = retry[0] + 1 if retry is not None else 1
            window = capped_exponential(
                n, LAUNCH_BACKOFF_BASE_SECONDS, LAUNCH_BACKOFF_MAX_SECONDS
            )
            self._launch_retry[claim.key] = (n, now + window * jitter())
            claim.status_conditions.set_false(COND_LAUNCHED, "LaunchFailed", str(err), now=now)
            self.kube.update(claim)
            return
        # crash window: the cloud instance EXISTS but the claim does
        # not record it yet — a restarted operator re-launches (one
        # live instance per claim) and GC reaps the unrecorded orphan
        from karpenter_tpu.solver import faults as _faults

        _faults.fire("crash_launch")
        self._launch_retry.pop(claim.key, None)
        claim.status.provider_id = launched.status.provider_id
        claim.status.image_id = launched.status.image_id
        claim.status.capacity = launched.status.capacity
        claim.status.allocatable = launched.status.allocatable
        claim.metadata.labels = launched.metadata.labels
        # single-valued requirements resolve to labels on the launched
        # claim (launch.go:131), so registration later stamps them onto
        # the node — e.g. a custom tier the scheduler pinned
        for spec in claim.spec.requirements:
            if spec.operator == "In" and len(spec.values) == 1:
                claim.metadata.labels.setdefault(spec.key, spec.values[0])
        claim.status_conditions.set_true(COND_LAUNCHED, now=now)
        self.kube.update(claim)

    # -- registration (registration.go:50-130) --------------------------------

    def _register(self, claim: NodeClaim, now: float) -> None:
        if claim.status_conditions.is_true(COND_REGISTERED) and claim.status.node_name:
            return
        node = self._node_for(claim)
        if node is None:
            return
        # sync labels/annotations; drop the unregistered taint
        node.metadata.labels.update(claim.metadata.labels)
        node.metadata.labels[NODE_REGISTERED_LABEL] = "true"
        node.metadata.annotations.update(claim.metadata.annotations)
        node.spec.taints = [
            t for t in node.spec.taints if t.key != UNREGISTERED_TAINT_KEY
        ]
        if TERMINATION_FINALIZER not in node.metadata.finalizers:
            node.metadata.finalizers.append(TERMINATION_FINALIZER)
        # the claim owns its Node (registration.go adds the controller
        # reference so a deleted claim cascades to the node object)
        if not any(
            r.kind == "NodeClaim" and r.name == claim.metadata.name
            for r in node.metadata.owner_references
        ):
            node.metadata.owner_references.append(OwnerReference(
                kind="NodeClaim", name=claim.metadata.name,
                uid=claim.metadata.uid, controller=True,
                api_version="karpenter.sh/v1",
            ))
        self.kube.update(node)
        claim.status.node_name = node.metadata.name
        claim.status_conditions.set_true(COND_REGISTERED, now=now)
        self.kube.update(claim)
        self.health.record(claim.metadata.labels.get(NODEPOOL_LABEL, ""), True)

    # -- initialization (initialization.go:46-134) -----------------------------

    def _initialize(self, claim: NodeClaim, now: float) -> None:
        if claim.status_conditions.is_true(COND_INITIALIZED):
            return
        node = self._node_for(claim)
        if node is None or not node.is_ready():
            return
        # startup taints must be gone
        startup_keys = {(t.key, t.effect) for t in claim.spec.startup_taints}
        for taint in node.spec.taints:
            if (taint.key, taint.effect) in startup_keys:
                return
            if is_ephemeral(taint):
                return
        # requested extended resources must be registered
        if not fits(claim.spec.resources, node.status.allocatable):
            return
        node.metadata.labels[NODE_INITIALIZED_LABEL] = "true"
        self.kube.update(node)
        claim.status_conditions.set_true(COND_INITIALIZED, now=now)
        self.kube.update(claim)

    # -- liveness (liveness.go:51-124) -----------------------------------------

    def _liveness(self, claim: NodeClaim, now: float) -> None:
        age = now - claim.metadata.creation_timestamp
        if not claim.status_conditions.is_true(COND_LAUNCHED):
            if age > LAUNCH_TIMEOUT_SECONDS:
                log.info("launch timeout for %s; deleting", claim.metadata.name)
                self.health.record(claim.metadata.labels.get(NODEPOOL_LABEL, ""), False)
                self._delete_claim(claim, now)
            return
        if not claim.status_conditions.is_true(COND_REGISTERED):
            if age > REGISTRATION_TIMEOUT_SECONDS:
                log.info("registration timeout for %s; deleting", claim.metadata.name)
                self.health.record(claim.metadata.labels.get(NODEPOOL_LABEL, ""), False)
                self._delete_claim(claim, now)

    # -- finalize (controller.go:184-273) --------------------------------------

    def _finalize(self, claim: NodeClaim, now: float) -> None:
        if TERMINATION_FINALIZER not in claim.metadata.finalizers:
            return
        # annotate the termination deadline from terminationGracePeriod
        tgp = parse_duration(claim.spec.termination_grace_period)
        if tgp is not None and (
            NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION not in claim.metadata.annotations
        ):
            claim.metadata.annotations[
                NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION
            ] = str(claim.metadata.deletion_timestamp + tgp)
            self.kube.update(claim)
        # delete node objects first; wait until they are gone
        nodes = [n for n in self.kube.nodes()
                 if n.spec.provider_id == claim.status.provider_id]
        if nodes:
            for node in nodes:
                if node.metadata.deletion_timestamp is None:
                    self.kube.delete(node, now=now)
            return
        if claim.status.provider_id:
            # await instance termination (controller.go:269-290): issue
            # the provider delete, mark InstanceTerminating, and hold
            # the finalizer until the provider reports the instance
            # GONE (NotFound) — dropping it on the first delete call
            # would let the claim vanish while the instance still runs,
            # leaking it to the garbage collector
            instance_gone = False
            try:
                self.cloud_provider.delete(claim)
            except NodeClaimNotFoundError:
                instance_gone = True
            claim.status_conditions.set_true(COND_INSTANCE_TERMINATING, now=now)
            if not instance_gone:
                return  # requeued; next pass re-checks the provider
        else:
            claim.status_conditions.set_true(COND_INSTANCE_TERMINATING, now=now)
        self.kube.remove_finalizer(claim, TERMINATION_FINALIZER)
        NODECLAIMS_TERMINATED.inc({
            "nodepool": claim.metadata.labels.get(NODEPOOL_LABEL, "")
        })

    # -- helpers ---------------------------------------------------------------

    def _node_for(self, claim: NodeClaim) -> Optional[Node]:
        for node in self.kube.nodes():
            if node.spec.provider_id == claim.status.provider_id:
                return node
        return None

    def _delete_claim(self, claim: NodeClaim, now: float) -> None:
        self.kube.delete(claim, now=now)
        # finalize immediately: nothing to tear down pre-launch
        self._finalize(claim, now)
