"""Flight recorder: end-to-end tick tracing with decision provenance.

The reference exposes pprof behind --enable-profiling and per-
controller tracing via controller-runtime; the operator profiler here
gives flat label->histogram latencies, but neither can answer the
question an operator actually asks when a fleet looks wrong: *which*
tick, *which* solve path, and *which* fault window produced this
NodeClaim. This module is the answer — structured spans over the whole
decision path:

    tick
    ├─ provision
    │  ├─ intake                 (pod counts, surge bursts)
    │  ├─ route                  (incremental vs full + reason)
    │  ├─ scheduler.solve
    │  │  ├─ solve.encode
    │  │  └─ solver.rung         (one per resilience-ladder attempt)
    │  │     ├─ solve.transfer / solve.compile / solve.execute
    │  │     ├─ solve.rpc        (trace id rides the service codec)
    │  │     └─ solve.decode
    │  ├─ admission              (priority shed counts)
    │  └─ create                 (claims written; provenance stamped)
    ├─ preemption / bind / interruption
    ├─ disruption.<method> / disruption.probe_batch
    ├─ disruption.validation / disruption.commit
    ├─ termination
    └─ kube.<write-verb>         (status + retry counts)

Design rules:

- **Determinism**: durations live in span start/end fields; `attrs`
  and `events` carry only decision provenance (counts, reasons,
  statuses, fault kinds) that replays identically under the same
  KARPENTER_FAULTS schedule. `structure()` strips ids and timings, so
  chaos suites assert byte-identical span TREES across replays — the
  decision-identity contract extended to the observability layer.
- **Healthy-path cost**: `span()` is a no-op (one global read) when no
  trace is open; the operator opens one root per tick. Tracing is on
  by default and disabled with KARPENTER_TRACE=0.
- **Cross-process**: the solver-service codec carries the trace id as
  an optional header field (old peers ignore it); the server `adopt()`s
  it so its ring entries resolve to the same id. Fault-injector replay
  log entries carry the trace id of the tick they fired in, launched
  NodeClaims carry it in the `karpenter.sh/provenance` annotation, and
  recorder events carry it too — any node on the fleet resolves back
  to the exact tick trace and fault window that produced it via
  /debug/traces.

The ring (`KARPENTER_TRACE_RING`, default 64 ticks) serves as JSON and
as Chrome-trace/Perfetto format from /debug/traces on the
observability server, is summarized in readyz()["last_tick_trace"],
and lands per bench arm as a p50/p99 per-span breakdown.
"""

from __future__ import annotations

import json
import os
import secrets
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Iterable, Optional

# the annotation launched NodeClaims (and recorder events) carry so a
# live object resolves back to the tick trace that produced it
PROVENANCE_ANNOTATION = "karpenter.sh/provenance"

ENV_ENABLED = "KARPENTER_TRACE"
ENV_RING = "KARPENTER_TRACE_RING"
DEFAULT_RING = 64

# attr keys every span may carry; everything in attrs/events MUST be
# deterministic under fault replay (see module docstring)


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "t0", "t1", "attrs", "events")

    def __init__(self, trace_id: str, span_id: int, parent_id: int,
                 name: str, t0: float):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.t1 = t0
        self.attrs: dict = {}
        self.events: list = []

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    def add_event(self, name: str, **attrs) -> None:
        self.events.append((name, attrs))


class _NullSpan:
    """The no-trace fast path: annotate/add_event are no-ops."""

    __slots__ = ()

    def annotate(self, **attrs) -> None:
        pass

    def add_event(self, name: str, **attrs) -> None:
        pass


_NULL = _NullSpan()


class Trace:
    """One open trace (a tick, or an adopted remote hop). Spans append
    under a lock — solver worker/watchdog threads record into the same
    trace the tick opened."""

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 clock=None):
        self.name = name
        self.trace_id = trace_id or secrets.token_hex(8)
        self.clock = clock if clock is not None else time.perf_counter
        self.started_at = time.time()
        self._lock = threading.Lock()
        self._next_id = 1
        self.root = Span(self.trace_id, 0, -1, name, self.clock())
        self.spans: list[Span] = [self.root]

    def new_span(self, name: str, parent: Span,
                 t0: Optional[float] = None) -> Span:
        with self._lock:
            span = Span(self.trace_id, self._next_id, parent.span_id,
                        name, self.clock() if t0 is None else t0)
            self._next_id += 1
            self.spans.append(span)
        return span

    def finish(self) -> dict:
        self.root.t1 = self.clock()
        base = self.root.t0
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "started_at": self.started_at,
            "duration_s": round(self.root.t1 - base, 9),
            "spans": [
                {
                    "span_id": s.span_id,
                    "parent_id": s.parent_id,
                    "name": s.name,
                    "t0_s": round(s.t0 - base, 9),
                    "t1_s": round(s.t1 - base, 9),
                    "attrs": dict(s.attrs),
                    "events": [
                        {"name": n, **a} for n, a in s.events
                    ],
                }
                for s in self.spans
            ],
        }


# -- module state -------------------------------------------------------------

_local = threading.local()
_ring_lock = threading.Lock()
_ring: "deque[dict]" = deque(maxlen=DEFAULT_RING)
# the process-globally active trace (the operator's open tick): threads
# with no thread-local trace of their own (resilience watchdogs, solver
# executors) attach their spans here
_active: Optional[Trace] = None


def enabled() -> bool:
    return os.environ.get(ENV_ENABLED, "1") != "0"


def ring_size() -> int:
    try:
        return max(1, int(os.environ.get(ENV_RING, str(DEFAULT_RING))))
    except ValueError:
        return DEFAULT_RING


def _resize_ring() -> None:
    global _ring
    size = ring_size()
    if _ring.maxlen != size:
        with _ring_lock:
            if _ring.maxlen != size:
                _ring = deque(_ring, maxlen=size)


def _current_trace() -> Optional[Trace]:
    trace = getattr(_local, "trace", None)
    return trace if trace is not None else _active


def _stack() -> list:
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


def current() -> Span:
    """The innermost open span on this thread (the active trace's root
    for threads with no local stack), or a no-op stand-in."""
    trace = _current_trace()
    if trace is None:
        return _NULL
    stack = _stack()
    # a stale stack from a previous trace must not parent new spans
    while stack and stack[-1].trace_id != trace.trace_id:
        stack.pop()
    return stack[-1] if stack else trace.root


def current_trace_id() -> str:
    trace = _current_trace()
    return trace.trace_id if trace is not None else ""


def annotate(**attrs) -> None:
    current().annotate(**attrs)


def add_event(name: str, **attrs) -> None:
    current().add_event(name, **attrs)


@contextmanager
def trace(name: str, clock=None, trace_id: Optional[str] = None,
          _global: bool = True):
    """Open a root trace (the operator's per-tick call). On exit the
    finished trace lands in the ring. No-op when KARPENTER_TRACE=0 or
    a trace is already open on this thread/process (nested opens — a
    bench harness around an operator — degrade to a plain span)."""
    global _active
    if not enabled():
        yield _NULL
        return
    # a nested global open degrades to a span (a bench harness around
    # an operator must not steal the tick's ring entry); an adopted
    # (non-global) hop always records its OWN segment — it stacks over
    # whatever trace this thread had open, so an in-process solver
    # service never folds into the operator's tick
    if _global and _current_trace() is not None:
        with span(name) as inner:
            yield inner
        return
    prev_trace = getattr(_local, "trace", None)
    # restore the ORIGINAL stack object, never a copy: spans open
    # around this trace captured that list at entry and pop it in
    # their exit handlers — restoring a copy would strand their
    # entries and mis-parent every later span under a closed one
    prev_stack = getattr(_local, "stack", None)
    t = Trace(name, trace_id=trace_id, clock=clock)
    _local.trace = t
    _local.stack = []
    if _global:
        _active = t
    try:
        yield t.root
    finally:
        _local.trace = prev_trace
        _local.stack = prev_stack if prev_stack is not None else []
        if _global and _active is t:
            _active = None
        _resize_ring()
        with _ring_lock:
            _ring.append(t.finish())


@contextmanager
def adopt(trace_id: str, name: str, clock=None):
    """The server side of a cross-process hop: record this thread's
    spans under the CALLER's trace id, as a separate ring entry —
    /debug/traces?trace_id= then returns both segments. Thread-local
    only: an in-process solver service must not capture the operator's
    globally-open tick."""
    with trace(name, clock=clock, trace_id=trace_id or None,
               _global=False) as root:
        yield root


@contextmanager
def span(name: str, **attrs):
    """One instrumented region. No active trace -> no-op (one global
    read). Spans created on threads without local context parent to
    the active trace's root."""
    trace_ = _current_trace()
    if trace_ is None:
        yield _NULL
        return
    parent = current()
    s = trace_.new_span(name, parent if isinstance(parent, Span)
                        else trace_.root)
    if attrs:
        s.attrs.update(attrs)
    stack = _stack()
    stack.append(s)
    try:
        yield s
    finally:
        s.t1 = trace_.clock()
        if stack and stack[-1] is s:
            stack.pop()


def record(name: str, t0: float, t1: float, **attrs) -> None:
    """A completed span from timestamps already taken (the solver's
    per-phase perf_counter pairs) — no extra clock reads, no nesting
    push/pop; parents to the innermost open span on this thread."""
    trace_ = _current_trace()
    if trace_ is None:
        return
    parent = current()
    s = trace_.new_span(name, parent if isinstance(parent, Span)
                        else trace_.root, t0=t0)
    s.t1 = t1
    if attrs:
        s.attrs.update(attrs)


# -- ring access --------------------------------------------------------------

def traces() -> list[dict]:
    """Ring contents, oldest first."""
    with _ring_lock:
        return list(_ring)


def find(trace_id: str) -> list[dict]:
    """Every ring segment recorded under `trace_id` (the tick trace
    plus any adopted remote hops)."""
    return [t for t in traces() if t["trace_id"] == trace_id]


def last_trace() -> Optional[dict]:
    with _ring_lock:
        return _ring[-1] if _ring else None


def clear() -> None:
    with _ring_lock:
        _ring.clear()


def summarize(trace_dict: Optional[dict]) -> Optional[dict]:
    """The readyz()["last_tick_trace"] digest: id, duration, span
    count, and the slowest spans."""
    if trace_dict is None:
        return None
    spans = trace_dict["spans"]
    slowest = sorted(
        ((s["name"], round(s["t1_s"] - s["t0_s"], 6)) for s in spans[1:]),
        key=lambda t: -t[1],
    )[:5]
    return {
        "trace_id": trace_dict["trace_id"],
        "name": trace_dict["name"],
        "started_at": trace_dict["started_at"],
        "duration_s": trace_dict["duration_s"],
        "span_count": len(spans),
        "slowest": slowest,
    }


# attrs excluded from structure(): coupled to wall-clock progress of
# background threads (the warm pool races its compiles against early
# ticks), so they legitimately differ across byte-identical replays.
# The "tm_" prefix marks device-telemetry attrs (solver/telemetry.py)
# wholesale — compiled-analysis availability tracks the background
# capture worker, and live memory_stats are timing-coupled by nature.
_NONSTRUCTURAL_ATTRS = frozenset({"warm_hit"})
_NONSTRUCTURAL_ATTR_PREFIX = "tm_"

# events excluded from structure(): the regression sentinel flags
# timing anomalies (metrics/sentinel.py), which machine load can trip
# in only one of two byte-identical fault replays
_NONSTRUCTURAL_EVENTS = frozenset({"sentinel_anomaly"})


def structure(trace_dict: dict) -> list:
    """The deterministic skeleton of a trace: nested
    (name, attrs, events, children) with ids, timings, and the few
    background-thread-coupled attrs stripped — what chaos suites
    compare across byte-identical fault replays."""
    children: dict[int, list[dict]] = {}
    for s in trace_dict["spans"]:
        children.setdefault(s["parent_id"], []).append(s)

    def node(s: dict) -> list:
        return [
            s["name"],
            tuple(sorted(
                (k, v) for k, v in s["attrs"].items()
                if k not in _NONSTRUCTURAL_ATTRS
                and not k.startswith(_NONSTRUCTURAL_ATTR_PREFIX)
            )),
            tuple(
                tuple(sorted(e.items())) for e in s["events"]
                if e.get("name") not in _NONSTRUCTURAL_EVENTS
            ),
            [node(c) for c in children.get(s["span_id"], [])],
        ]

    roots = children.get(-1, [])
    return [node(r) for r in roots]


def span_stats(trace_dicts: Iterable[dict]) -> dict[str, dict]:
    """Per-span-name latency breakdown over a set of traces: count,
    total, p50/p99/max — the per-arm digest bench artifacts carry."""
    samples: dict[str, list[float]] = {}
    for t in trace_dicts:
        for s in t["spans"]:
            samples.setdefault(s["name"], []).append(s["t1_s"] - s["t0_s"])
    out = {}
    for name, vals in sorted(samples.items()):
        vals.sort()
        n = len(vals)
        out[name] = {
            "count": n,
            "total_s": round(sum(vals), 6),
            "p50_s": round(vals[n // 2], 6),
            "p99_s": round(vals[min(n - 1, (99 * n) // 100)], 6),
            "max_s": round(vals[-1], 6),
        }
    return out


def to_chrome(trace_dicts: Iterable[dict]) -> dict:
    """Chrome-trace/Perfetto JSON ("X" complete events, µs): load the
    /debug/traces?format=perfetto payload straight into ui.perfetto.dev
    or chrome://tracing."""
    events = []
    for idx, t in enumerate(trace_dicts):
        base_us = t["started_at"] * 1e6
        for s in t["spans"]:
            events.append({
                "name": s["name"],
                "cat": t["name"],
                "ph": "X",
                "ts": base_us + s["t0_s"] * 1e6,
                "dur": max(0.0, (s["t1_s"] - s["t0_s"]) * 1e6),
                "pid": 1,
                "tid": idx + 1,
                "args": {
                    "trace_id": t["trace_id"],
                    "span_id": s["span_id"],
                    **s["attrs"],
                    **(
                        {"events": s["events"]} if s["events"] else {}
                    ),
                },
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def render_json(trace_id: Optional[str] = None) -> str:
    """The /debug/traces body: the whole ring, or one trace's
    segments."""
    if trace_id:
        return json.dumps({"traces": find(trace_id)})
    return json.dumps({"traces": traces()})
